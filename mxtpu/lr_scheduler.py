"""Learning-rate schedulers — parity with ``python/mxnet/lr_scheduler.py``."""

from __future__ import annotations

import math

from .base import capture_init_spec

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler", "PolyScheduler",
           "CosineScheduler", "WarmupScheduler"]


class LRScheduler:
    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        capture_init_spec(cls)

    def __init__(self, base_lr: float = 0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


capture_init_spec(LRScheduler)


class FactorScheduler(LRScheduler):
    """lr = base_lr * factor^(floor(num_update/step)) with stop_factor_lr floor."""

    def __init__(self, step: int, factor: float = 1.0, stop_factor_lr: float = 1e-8,
                 base_lr: float = 0.01):
        super().__init__(base_lr)
        if step < 1:
            raise ValueError("step must be >= 1")
        self.step, self.factor, self.stop_factor_lr = step, factor, stop_factor_lr

    def __call__(self, num_update: int) -> float:
        lr = self.base_lr * (self.factor ** (num_update // self.step))
        return max(lr, self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """Drop by ``factor`` at each step in a sorted step list."""

    def __init__(self, step, factor: float = 1.0, base_lr: float = 0.01):
        super().__init__(base_lr)
        self.steps = sorted(step)
        self.factor = factor

    def __call__(self, num_update: int) -> float:
        lr = self.base_lr
        for s in self.steps:
            if num_update >= s:
                lr *= self.factor
        return lr


class PolyScheduler(LRScheduler):
    """Polynomial decay to final_lr over max_update steps."""

    def __init__(self, max_update: int, base_lr: float = 0.01, pwr: int = 2,
                 final_lr: float = 0.0):
        super().__init__(base_lr)
        self.max_update, self.pwr, self.final_lr = max_update, pwr, final_lr

    def __call__(self, num_update: int) -> float:
        if num_update >= self.max_update:
            return self.final_lr
        frac = 1.0 - num_update / self.max_update
        return self.final_lr + (self.base_lr - self.final_lr) * (frac ** self.pwr)


class CosineScheduler(LRScheduler):
    def __init__(self, max_update: int, base_lr: float = 0.01, final_lr: float = 0.0):
        super().__init__(base_lr)
        self.max_update, self.final_lr = max_update, final_lr

    def __call__(self, num_update: int) -> float:
        if num_update >= self.max_update:
            return self.final_lr
        cos = (1 + math.cos(math.pi * num_update / self.max_update)) / 2
        return self.final_lr + (self.base_lr - self.final_lr) * cos


class WarmupScheduler(LRScheduler):
    """Linear warmup wrapper around another scheduler (ubiquitous on TPU pods where
    large global batches need it; the reference handles this ad hoc in examples)."""

    def __init__(self, base_scheduler: LRScheduler, warmup_steps: int,
                 warmup_begin_lr: float = 0.0):
        super().__init__(base_scheduler.base_lr)
        self.sched = base_scheduler
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr

    def __call__(self, num_update: int) -> float:
        if num_update < self.warmup_steps:
            frac = num_update / max(1, self.warmup_steps)
            return self.warmup_begin_lr + (self.base_lr - self.warmup_begin_lr) * frac
        return self.sched(num_update - self.warmup_steps)
