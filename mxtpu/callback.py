"""Training callbacks — parity with ``python/mxnet/callback.py`` (Speedometer,
do_checkpoint, log_train_metric, ProgressBar)."""

from __future__ import annotations

import logging
import time
from typing import NamedTuple, Optional


class BatchEndParam(NamedTuple):
    epoch: int
    nbatch: int
    eval_metric: object
    locals: Optional[dict] = None


class Speedometer:
    """Throughput logger (callback.py Speedometer): samples/sec every
    ``frequent``. When the device-feed input pipeline is active, each line
    also reports the input-stall per batch since the last print and the
    prefetch queue high-water mark (``profiler.get_feed_stats()``) — the
    at-a-glance "is training input-bound?" readout. When the fit loop has
    been recording step latencies (``observability.flops`` ring), the line
    also carries the rolling p50/p99 step time — the tail-latency readout
    the MFU scoreboard ratchets on."""

    def __init__(self, batch_size: int, frequent: int = 50, auto_reset: bool = True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0
        self._feed_consumed = 0
        self._feed_stall_ms = 0.0
        self._comm_steps = 0
        self._comm_bytes = 0

    def _feed_msg(self) -> str:
        """Δ input-stall per batch since the last print ('' if no feed ran)."""
        from . import profiler
        f = profiler.get_feed_stats()
        consumed = f["batches_consumed"] - self._feed_consumed
        stall = f["stall_ms_total"] - self._feed_stall_ms
        self._feed_consumed = f["batches_consumed"]
        self._feed_stall_ms = f["stall_ms_total"]
        if consumed <= 0:
            return ""
        return (f"\tinput-stall: {stall / consumed:.2f} ms/batch "
                f"(queue hw {f['queue_depth_max']}/{f['feed_depth']})")

    def _step_msg(self) -> str:
        """Rolling p50/p99 step latency from the observability step ring
        ('' when nothing recorded a step — e.g. outside ``Module.fit``)."""
        from .observability import flops
        s = flops.get_mfu_stats()
        if not s["steps"]:
            return ""
        return (f"\tstep: p50={s['p50_step_ms']:.2f} ms "
                f"p99={s['p99_step_ms']:.2f} ms")

    def _comm_msg(self) -> str:
        """Δ gradient-comm per step since the last print ('' when no ZeRO
        steps ran) — the at-a-glance "what does a step ship over ICI?"
        readout (``profiler.get_comm_stats()``)."""
        from . import profiler
        c = profiler.get_comm_stats()
        steps = c["zero_steps"] - self._comm_steps
        total = c["bytes_reduced"] + c["bytes_gathered"]
        delta = total - self._comm_bytes
        self._comm_steps = c["zero_steps"]
        self._comm_bytes = total
        if steps <= 0:
            return ""
        return (f"\tcomm: {delta / steps / 1e6:.2f} MB/step "
                f"(ZeRO-1 dp={c['dp']}, {c['bucket_count']} bucket(s), "
                f"shard {c['shard_bytes_per_device'] / 1e6:.2f} MB/dev)")

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                # clamp: two boundaries can land within one time.time() tick
                # (coarse clocks / fused fast steps) — never divide by zero
                elapsed = max(time.time() - self.tic, 1e-9)
                speed = self.frequent * self.batch_size / elapsed
                feed = self._feed_msg() + self._comm_msg() + self._step_msg()
                if param.eval_metric is not None:
                    nv = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "\t".join(f"{n}={v:.6f}" for n, v in nv)
                    logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s%s",
                                 param.epoch, count, speed, msg, feed)
                else:
                    logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                                 param.epoch, count, speed, feed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


def do_checkpoint(prefix, period: int = 1, module=None, trainer=None):
    """Epoch-end checkpoint callback (callback.py module_checkpoint parity).

    ``prefix`` may be a path prefix (legacy ``prefix-####.params`` layout,
    written atomically through ``checkpoint.save_legacy``) or a
    ``checkpoint.CheckpointManager`` — then the save is ASYNC (background
    writer, atomic step-dir commit). Pass ``module=`` (and optionally
    ``trainer=``) in manager mode to capture the FULL resumable state —
    optimizer slots and RNG — not just params; ``Module.fit(resume_from=...)``
    picks the run up from it.
    """
    period = max(1, int(period))

    def _callback(epoch, sym, arg_params, aux_params):
        if (epoch + 1) % period == 0:
            from .checkpoint import CheckpointManager
            if isinstance(prefix, CheckpointManager):
                # epoch meta records the NEXT epoch: everything up to and
                # including `epoch` is complete, resume starts cleanly after
                if module is not None:
                    prefix.save(epoch + 1, module=module, trainer=trainer,
                                epoch=epoch + 1)
                else:
                    prefix.save(epoch + 1, arg_params=arg_params,
                                aux_params=aux_params, epoch=epoch + 1,
                                extra_meta={"symbol": getattr(sym, "name",
                                                              None)})
            else:
                from .model import save_checkpoint
                save_checkpoint(prefix, epoch + 1, sym, arg_params, aux_params)

    return _callback


def log_train_metric(period: int, auto_reset: bool = False):
    def _callback(param: BatchEndParam):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            nv = param.eval_metric.get_name_value()
            msg = "\t".join(f"{n}={v:.6f}" for n, v in nv)
            logging.info("Iter[%d] Batch[%d] Train-%s", param.epoch, param.nbatch, msg)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class ProgressBar:
    def __init__(self, total: int, length: int = 80):
        self.total = total
        self.length = length

    def __call__(self, param: BatchEndParam):
        filled = int(round(self.length * param.nbatch / float(self.total)))
        bar = "=" * filled + "-" * (self.length - filled)
        print(f"\r[{bar}] {param.nbatch}/{self.total}", end="", flush=True)
