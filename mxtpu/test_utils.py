"""Test utilities — parity with ``python/mxnet/test_utils.py`` (the workhorse of the
reference's operator tests, SURVEY.md §4): assert_almost_equal w/ per-dtype tolerances,
check_numeric_gradient, check_consistency (CPU-vs-accelerator), rand_ndarray."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from . import autograd
from . import ndarray as nd
from .context import cpu, current_context
from .ndarray.ndarray import NDArray

_DTYPE_TOL = {
    np.dtype(np.float16): (1e-2, 1e-2),
    np.dtype(np.float32): (1e-4, 1e-5),
    np.dtype(np.float64): (1e-6, 1e-8),
}


def default_rtol_atol(dtype) -> tuple:
    return _DTYPE_TOL.get(np.dtype(dtype), (1e-4, 1e-5))


def assert_almost_equal(a, b, rtol: Optional[float] = None,
                        atol: Optional[float] = None, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(a.dtype)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} vs {names[1]}")


def almost_equal(a, b, rtol=None, atol=None) -> bool:
    try:
        assert_almost_equal(a, b, rtol, atol)
        return True
    except AssertionError:
        return False


def rand_ndarray(shape, dtype="float32", scale: float = 1.0) -> NDArray:
    return nd.array((np.random.randn(*shape) * scale).astype(dtype))


def rand_shape_nd(ndim: int, dim: int = 10) -> tuple:
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def check_numeric_gradient(fn: Callable, inputs: Sequence[NDArray],
                           eps: float = 1e-3, rtol: float = 1e-2,
                           atol: float = 1e-3, loss_fn: Optional[Callable] = None):
    """Finite-difference vs autograd gradients (test_utils.py:check_numeric_gradient
    — SURVEY §4's "workhorse of operator tests").

    ``fn(*inputs) -> NDArray`` is differentiated through the imperative tape
    (non-scalar outputs get a ones cotangent). The numeric side differentiates
    ``loss_fn`` (default: ``fn``, which must then be scalar). Pass a separate
    ``loss_fn`` for the legacy loss heads whose custom backward injects the
    gradient of an IMPLIED loss while their forward returns predictions
    (SoftmaxOutput: forward=softmax, backward=d CE/d data — the numeric oracle
    must difference the CE, not the softmax).
    """
    numeric_fn = loss_fn if loss_fn is not None else fn
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
    out.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for xi, x in enumerate(inputs):
        dt = x.asnumpy().dtype                       # preserve input dtype
        arr = x.asnumpy().astype(np.float64)
        numeric = np.zeros_like(arr)
        flat = arr.ravel()
        num_flat = numeric.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            x._set_data(np.asarray(arr, dt).reshape(x.shape))
            f_plus = float(numeric_fn(*inputs).asscalar())
            flat[i] = orig - eps
            x._set_data(np.asarray(arr, dt).reshape(x.shape))
            f_minus = float(numeric_fn(*inputs).asscalar())
            flat[i] = orig
            x._set_data(np.asarray(arr, dt).reshape(x.shape))
            num_flat[i] = (f_plus - f_minus) / (2 * eps)
        np.testing.assert_allclose(analytic[xi], numeric, rtol=rtol, atol=atol,
                                   err_msg=f"gradient mismatch on input {xi}")


def check_consistency(fn: Callable, inputs: Sequence[np.ndarray],
                      ctx_list=None, rtol: float = 1e-3, atol: float = 1e-4):
    """Run fn on each context and compare outputs (CPU is the oracle — the
    reference's GPU-vs-CPU check, test_utils.py:check_consistency)."""
    from .context import Context
    ctx_list = ctx_list or [cpu(0), current_context()]
    results = []
    for ctx in ctx_list:
        args = [nd.array(a, ctx=ctx) for a in inputs]
        out = fn(*args)
        results.append(out.asnumpy())
    for r in results[1:]:
        np.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)


def same(a, b) -> bool:
    return np.array_equal(np.asarray(a), np.asarray(b))


class DummyIter:
    """Infinite synthetic-batch iterator (test_utils simple_forward helpers)."""

    def __init__(self, batch):
        self.batch = batch

    def __iter__(self):
        return self

    def __next__(self):
        return self.batch
