"""Python support layer for the native C ABI (native/mxtpu_capi.cc).

The reference exposes a predict-only C ABI (``include/mxnet/c_predict_api.h``,
``src/c_api/c_predict_api.cc``: MXPredCreate/SetInput/Forward/GetOutput) so any
language with a C FFI can run inference from a symbol-JSON + params checkpoint.
In the TPU-native design the compute path is JAX, so the stable C boundary embeds
(or attaches to) the CPython interpreter and drives this module; the C side stays
a thin marshalling shim (buffers in, buffers out) while graph loading, shape
inference, and execution reuse the framework's own Symbol/Executor stack.

Checkpoint convention matches ``mxtpu.model.save_checkpoint`` (and the reference's
model.py:384): symbol JSON from ``Symbol.tojson`` + an ``arg:``/``aux:``-prefixed
params file (nd.save format).
"""

from __future__ import annotations

import io
from typing import Dict, List, Sequence, Tuple

import numpy as np

__all__ = ["Predictor", "create_predictor", "load_param_bytes"]


def load_param_bytes(param_bytes: bytes) -> Tuple[Dict, Dict]:
    """Split a params payload (nd.save npz format, or the reference's
    NDARRAY_V2 binary — sniffed by magic) into (arg_params, aux_params),
    stripping the reference's ``arg:``/``aux:`` prefixes (c_predict_api.cc
    does the same split when creating a predictor). Empty bytes → a predictor
    whose arguments all arrive via MXPredSetInput (the pure-C compose loop)."""
    from .ndarray.ndarray import _SAVE_FORMAT_KEY, _decode_entries

    if not param_bytes:
        return {}, {}
    from .ndarray import legacy_io
    if legacy_io.is_reference_file(param_bytes[:8]):
        entries = legacy_io.load_bytes(param_bytes)
        if isinstance(entries, list):
            entries = {f"arr_{i}": v for i, v in enumerate(entries)}
    else:
        with np.load(io.BytesIO(param_bytes), allow_pickle=False) as z:
            keys = [k for k in z.keys() if k != _SAVE_FORMAT_KEY]
            entries = _decode_entries(z, keys)
    arg_params, aux_params = {}, {}
    for k, v in entries.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


class Predictor:
    """One bound inference executor behind a C ``PredictorHandle``."""

    def __init__(self, symbol_json: str, param_bytes: bytes,
                 input_names: Sequence[str],
                 input_shapes: Sequence[Sequence[int]],
                 dev_type: int = 1, dev_id: int = 0):
        from . import context
        from .symbol import load_json

        if len(input_names) != len(input_shapes):
            raise ValueError("input_keys and input_shapes length mismatch")
        sym = load_json(symbol_json)
        arg_names = set(sym.list_arguments())
        for n in input_names:
            if n not in arg_names:
                raise KeyError(
                    f"declared input {n!r} is not an argument of the symbol "
                    f"(arguments: {sorted(arg_names)})")
        arg_params, aux_params = load_param_bytes(param_bytes)
        self._input_names = list(input_names)
        self._input_shapes = {n: tuple(int(d) for d in s)
                              for n, s in zip(input_names, input_shapes)}
        # dev_type follows the reference's enum (1=cpu, 2=gpu); the accelerator
        # slot maps to the TPU context here
        ctx = context.cpu(dev_id) if dev_type == 1 else context.tpu(dev_id)
        self._exec = sym.simple_bind(ctx=ctx, grad_req="null",
                                     **self._input_shapes)
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        self._outputs: List[np.ndarray] = []
        self.forward()  # c_predict_api.cc runs an initial forward on create

    # -- C-boundary entry points (flat buffers only) -------------------------
    def set_input(self, key: str, data: bytes) -> None:
        """Copy a float32 buffer into the named input (MXPredSetInput)."""
        if key not in self._input_shapes:
            raise KeyError(f"unknown input {key!r}; declared: "
                           f"{self._input_names}")
        shape = self._input_shapes[key]
        arr = np.frombuffer(data, dtype=np.float32)
        expect = int(np.prod(shape)) if shape else 1
        if arr.size != expect:
            raise ValueError(f"input {key!r} expects {expect} floats "
                             f"(shape {shape}), got {arr.size}")
        import jax.numpy as jnp
        self._exec.arg_dict[key]._set_data(jnp.asarray(arr.reshape(shape)))

    def forward(self) -> None:
        self._exec.forward(is_train=False)
        self._outputs = [np.asarray(o.data, dtype=np.float32)
                         for o in self._exec.outputs]

    @property
    def num_outputs(self) -> int:
        return len(self._outputs)

    def output_shape(self, index: int) -> Tuple[int, ...]:
        return tuple(int(d) for d in self._outputs[index].shape)

    def get_output(self, index: int) -> bytes:
        """Return output ``index`` as a contiguous float32 buffer."""
        return np.ascontiguousarray(self._outputs[index],
                                    dtype=np.float32).tobytes()


def create_predictor(symbol_json: str, param_bytes: bytes,
                     input_names: Sequence[str],
                     input_shapes: Sequence[Sequence[int]],
                     dev_type: int = 1, dev_id: int = 0) -> Predictor:
    """Factory the C side calls (keeps the C code to one attribute lookup)."""
    return Predictor(symbol_json, param_bytes, input_names, input_shapes,
                     dev_type, dev_id)


# ---------------------------------------------------------------------------
# training ABI support (native/mxtpu_capi.cc MXNDArray* / MXImperativeInvoke /
# MXAutograd* — the imperative slice of the reference's c_api.h:
# MXNDArrayCreateEx :119, MXImperativeInvokeEx (c_api_ndarray.cc:81),
# MXAutogradMarkVariables / MXAutogradBackwardEx (c_api_ndarray.cc:319-396)).
# Handles crossing the C boundary ARE the NDArray PyObjects (the C side owns
# a reference); this layer stays flat-buffers-in/objects-out.
# ---------------------------------------------------------------------------

def nd_create(shape, dtype_code: int):
    import jax.numpy as jnp

    from .base import dtype_from_id, dtype_np
    from .ndarray.ndarray import NDArray
    # the one framework-wide mshadow dtype enum (base.py:_DTYPE_ID — covers
    # bool and bfloat16 too)
    dt = dtype_np(dtype_from_id(int(dtype_code)))
    return NDArray(jnp.zeros(tuple(int(d) for d in shape), dt))


def nd_shape(arr):
    return tuple(int(d) for d in arr.shape)


def nd_dtype_code(arr) -> int:
    from .base import dtype_id
    return dtype_id(np.dtype(arr.dtype).name)


def nd_copy_from(arr, data: bytes) -> None:
    import jax.numpy as jnp
    host = np.frombuffer(data, dtype=np.dtype(arr.dtype)).reshape(arr.shape)
    arr._set_data(jnp.asarray(host))


def nd_copy_to(arr) -> bytes:
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def _parse_param(v: str):
    """Reference convention: op attrs cross the C boundary as STRINGS
    (MXImperativeInvokeEx param_vals); parse python-literal-looking ones."""
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def invoke_op(name: str, inputs, param_keys, param_vals):
    """Run a registry op imperatively; returns a LIST of NDArray outputs."""
    from .ops import registry as reg
    op = reg.get_op(name)
    kwargs = {k: _parse_param(v) for k, v in zip(param_keys, param_vals)}
    out = reg.invoke(op, *inputs, **kwargs)
    return list(out) if isinstance(out, tuple) else [out]


def list_op_names():
    from .ops import registry as reg
    return reg.list_ops()


def autograd_set_recording(flag: int) -> int:
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def autograd_set_training(flag: int) -> int:
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def autograd_mark_variables(arrs, grad_reqs) -> None:
    from . import autograd
    req_names = {0: "null", 1: "write", 2: "add"}
    autograd.mark_variables(
        list(arrs), grad_reqs=[req_names[int(r)] for r in grad_reqs])


def autograd_backward(heads, head_grads, retain_graph: int) -> None:
    from . import autograd
    hg = None if not head_grads else list(head_grads)
    autograd.backward(list(heads), head_grads=hg,
                      retain_graph=bool(retain_graph))


def nd_get_grad(arr):
    g = arr.grad
    if g is None:
        raise ValueError("array has no gradient (not marked, or no backward "
                         "has run)")
    return g


# ---------------------------------------------------------------------------
# KVStore C surface (reference c_api.h MXKVStoreCreate :1359 / Init / PushEx /
# PullEx / GetRank / GetGroupSize / Barrier / Free). Handles are KVStore
# PyObjects; values are the same NDArray handles the training ABI uses. The
# reference's MXKVStoreSetUpdater C-callback is replaced by the restricted
# optimizer spec (name + JSON kwargs — the PS wire format of mxtpu/ps.py),
# which also works for the dist_async server role.
# ---------------------------------------------------------------------------


def kv_create(kv_type: str):
    from . import kvstore
    return kvstore.create(kv_type)


def kv_init(kv, keys, vals) -> None:
    kv.init(list(keys), list(vals))


def kv_push(kv, keys, vals) -> None:
    kv.push(list(keys), list(vals))


def kv_pull(kv, keys, outs) -> None:
    kv.pull(list(keys), out=list(outs))


def kv_rank(kv) -> int:
    return int(kv.rank)


def kv_size(kv) -> int:
    return int(kv.num_workers)


def kv_barrier(kv) -> None:
    kv.barrier()


def kv_set_optimizer(kv, spec_json: str) -> None:
    import json as _json

    from . import optimizer as opt_mod
    spec = _json.loads(spec_json)
    kv.set_optimizer(opt_mod.create(spec["name"], **spec.get("kwargs", {})))


# ---------------------------------------------------------------------------
# Symbol C surface (reference c_api_symbolic.cc: MXSymbolCreateAtomicSymbol /
# MXSymbolCreateVariable / MXSymbolCreateFromJSON / MXSymbolCompose /
# MXSymbolSaveToJSON / MXSymbolListArguments|Outputs|AuxiliaryStates /
# MXSymbolInferShape). A SymbolHandle is a SymbolBox PyObject: an atomic
# (un-composed) op descriptor until MXSymbolCompose binds its inputs in place
# — the reference's two-step create/compose protocol — and a real Symbol
# afterwards. A pure C client can therefore BUILD a graph, infer its shapes,
# serialize it, and hand the JSON to MXPredCreate: no Python-authored JSON
# anywhere in the loop.
# ---------------------------------------------------------------------------


class SymbolBox:
    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload       # ("atomic", op_name, attrs) | Symbol


def _unbox(box):
    if isinstance(box.payload, tuple):
        raise ValueError(
            f"symbol is an un-composed atomic op {box.payload[1]!r}: call "
            "MXSymbolCompose first")
    return box.payload


def sym_create_variable(name: str):
    from . import symbol
    return SymbolBox(symbol.Variable(name))


def sym_create_from_json(json_str: str):
    from . import symbol
    return SymbolBox(symbol.load_json(json_str))


def sym_create_atomic(op_name: str, param_keys, param_vals):
    from .ops import registry as reg
    reg.get_op(op_name)              # fail fast on unknown op
    attrs = {k: _parse_param(v)
             for k, v in zip(param_keys, param_vals)}
    return SymbolBox(("atomic", op_name, attrs))


def sym_compose(box, name, in_keys, in_boxes) -> None:
    """MXSymbolCompose semantics: bind inputs into the atomic symbol IN PLACE
    (c_api_symbolic.cc MXSymbolCompose). All-empty keys → positional; mixed
    keyword/positional is rejected, as in the reference."""
    from .symbol import make_op_wrapper
    if not isinstance(box.payload, tuple):
        raise ValueError("MXSymbolCompose: symbol was already composed")
    _, op_name, attrs = box.payload
    ins = [_unbox(b) for b in in_boxes]
    wrapper = make_op_wrapper(op_name)
    kw = dict(attrs)
    n_named = sum(1 for k in in_keys if k)
    if n_named and n_named != len(list(in_keys)):
        raise ValueError(
            "MXSymbolCompose: keyword and positional inputs cannot be mixed "
            "(provide keys for all inputs or for none)")
    if n_named:
        named = dict(zip(in_keys, ins))
        box.payload = wrapper(name=name or None, **named, **kw)
    else:
        box.payload = wrapper(*ins, name=name or None, **kw)


def sym_tojson(box) -> str:
    return _unbox(box).tojson()


def sym_list_arguments(box):
    return list(_unbox(box).list_arguments())


def sym_list_outputs(box):
    return list(_unbox(box).list_outputs())


def sym_list_aux(box):
    return list(_unbox(box).list_auxiliary_states())


def sym_infer_shape(box, keys, shapes):
    """Returns (arg_shapes, out_shapes, aux_shapes, complete). Unknown entries
    (underdetermined inference) serialize as () with complete=0 — a genuine
    scalar shape also serializes as () but with complete=1, the reference's
    convention. Real errors (contradictory shapes, unknown names) RAISE so
    the C boundary returns -1 with the message in MXGetLastError."""
    s = _unbox(box)
    feeds = {k: tuple(int(d) for d in shp) for k, shp in zip(keys, shapes)}
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(**feeds)
    complete = int(all(t is not None
                       for grp in (arg_shapes, out_shapes, aux_shapes)
                       for t in (grp or [])))
    def clean(lst):
        return [tuple(int(d) for d in t) if t is not None else ()
                for t in (lst or [])]
    return (clean(arg_shapes), clean(out_shapes), clean(aux_shapes), complete)
